// Native data-loader core: fused shuffle-gather + random-crop + hflip.
//
// The TPU-native answer to the reference's vendored multiprocess DataLoader
// (my_data_loader.py:37-75 worker processes): the augmentation hot path as a
// single threaded C++ pass instead of Python worker processes. The Python
// loop (data/augment.py crop_flip_prepadded) pays per-image interpreter
// overhead and holds the GIL; this kernel copies each output row with
// memcpy (or a reversed per-pixel copy when flipped) across an OpenMP team,
// and the ctypes call releases the GIL for the whole batch.
//
// Layout contract (matches the pre-padded store in data/datasets.py):
//   padded: [N, PH, PW, C] uint8, C-contiguous
//   out:    [B, H,  W,  C] uint8, C-contiguous
//   sel/ys/xs/flip: int64/int32/int32/uint8 [B]
// ys/xs are the crop offsets into the padded image; flip reverses W.

#include <cstdint>
#include <cstring>

extern "C" {

void psl_crop_flip_batch(const uint8_t *padded, const int64_t *sel,
                         const int32_t *ys, const int32_t *xs,
                         const uint8_t *flip, uint8_t *out,
                         int64_t b, int64_t h, int64_t w, int64_t c,
                         int64_t ph, int64_t pw) {
    const int64_t img_in = ph * pw * c;
    const int64_t row_in = pw * c;
    const int64_t img_out = h * w * c;
    const int64_t row_out = w * c;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < b; ++i) {
        const uint8_t *src_img =
            padded + sel[i] * img_in + ys[i] * row_in + xs[i] * c;
        uint8_t *dst_img = out + i * img_out;
        if (!flip[i]) {
            for (int64_t r = 0; r < h; ++r)
                std::memcpy(dst_img + r * row_out, src_img + r * row_in,
                            row_out);
        } else {
            for (int64_t r = 0; r < h; ++r) {
                const uint8_t *src_row = src_img + r * row_in;
                uint8_t *dst_row = dst_img + r * row_out;
                for (int64_t x = 0; x < w; ++x)
                    std::memcpy(dst_row + x * c,
                                src_row + (w - 1 - x) * c, c);
            }
        }
    }
}

}  // extern "C"
