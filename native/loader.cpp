// Native data-loader core: fused shuffle-gather + random-crop + hflip,
// plus the ImageNet-geometry random-resized-crop kernel.
//
// The TPU-native answer to the reference's vendored multiprocess DataLoader
// (my_data_loader.py:37-75 worker processes): the augmentation hot path as a
// single threaded C++ pass instead of Python worker processes. The Python
// loop (data/augment.py crop_flip_prepadded) pays per-image interpreter
// overhead and holds the GIL; this kernel copies each output row with
// memcpy (or a reversed per-pixel copy when flipped) across an OpenMP team,
// and the ctypes call releases the GIL for the whole batch.
//
// Layout contract (matches the pre-padded store in data/datasets.py):
//   padded: [N, PH, PW, C] uint8, C-contiguous
//   out:    [B, H,  W,  C] uint8, C-contiguous
//   sel/ys/xs/flip: int64/int32/int32/uint8 [B]
// ys/xs are the crop offsets into the padded image; flip reverses W.

#include <cstdint>
#include <cstring>
#include <vector>

// ---------------------------------------------------------------------------
// Random-resized-crop (ImageNet geometry): crop rect -> bilinear resize ->
// hflip, uint8 in/out. ALL arithmetic is integer fixed-point (PSL_SHIFT
// fractional bits per axis) so the numpy fallback in data/augment.py is
// bit-identical by construction — no float path exists for -ffast-math /
// FMA contraction to perturb. Crop rectangles and flips are sampled
// host-side from a counter-based RNG (shared with the fallback); this
// kernel only executes them.
//
// Sampling positions follow the half-pixel convention:
//   src = (t + 0.5) * crop / out - 0.5  ==  ((2t+1)*crop - out) / (2*out)
// quantized to PSL_SHIFT fractional bits, edges clamped. The resize is
// separable: one horizontal pass per needed crop row into a rolling
// two-row int32 cache, then a vertical combine per output row — each crop
// row is resized at most once and the intermediate stays L1-resident.
static const int PSL_SHIFT = 10;                 // weights in [0, 1<<10]
static const int32_t PSL_ONE = 1 << PSL_SHIFT;   // h-pass result <= 255<<10
                                                 // v-pass acc   <= 255<<20

// Per-output-index sampling tables for one axis: i0/i1 source indices and
// w0/w1 fixed-point weights. Mirrors _rrc_axis_tables in data/augment.py
// exactly (same integer expressions, same clamps).
static void psl_axis_tables(int64_t crop, int64_t out, int32_t *i0,
                            int32_t *i1, int32_t *w0, int32_t *w1) {
    for (int64_t t = 0; t < out; ++t) {
        const int64_t num = (2 * t + 1) * crop - out;
        int64_t fp = num > 0 ? (num << PSL_SHIFT) / (2 * out) : 0;
        int64_t a = fp >> PSL_SHIFT;
        int32_t fr = (int32_t)(fp & (PSL_ONE - 1));
        if (a >= crop - 1) { a = crop - 1; fr = 0; }
        i0[t] = (int32_t)a;
        i1[t] = (int32_t)(a < crop - 1 ? a + 1 : a);
        w0[t] = PSL_ONE - fr;
        w1[t] = fr;
    }
}

extern "C" {

void psl_crop_flip_batch(const uint8_t *padded, const int64_t *sel,
                         const int32_t *ys, const int32_t *xs,
                         const uint8_t *flip, uint8_t *out,
                         int64_t b, int64_t h, int64_t w, int64_t c,
                         int64_t ph, int64_t pw) {
    const int64_t img_in = ph * pw * c;
    const int64_t row_in = pw * c;
    const int64_t img_out = h * w * c;
    const int64_t row_out = w * c;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < b; ++i) {
        const uint8_t *src_img =
            padded + sel[i] * img_in + ys[i] * row_in + xs[i] * c;
        uint8_t *dst_img = out + i * img_out;
        if (!flip[i]) {
            for (int64_t r = 0; r < h; ++r)
                std::memcpy(dst_img + r * row_out, src_img + r * row_in,
                            row_out);
        } else {
            for (int64_t r = 0; r < h; ++r) {
                const uint8_t *src_row = src_img + r * row_in;
                uint8_t *dst_row = dst_img + r * row_out;
                for (int64_t x = 0; x < w; ++x)
                    std::memcpy(dst_row + x * c,
                                src_row + (w - 1 - x) * c, c);
            }
        }
    }
}

// Random-resized-crop batch: for each output image i, crop
// src[sel[i]][ys[i]:ys[i]+hs[i], xs[i]:xs[i]+ws[i]] and bilinear-resize it
// to [oh, ow], mirroring columns when flip[i].
//   src: [N, SH, SW, C] uint8   out: [B, OH, OW, C] uint8   (C-contiguous)
//   sel int64[B]; ys/xs/hs/ws int32[B]; flip uint8[B]
// Crop rects must satisfy 1 <= hs <= SH - ys, 1 <= ws <= SW - xs (the
// host-side sampler guarantees this; out-of-range rects read garbage).
void psl_rrc_batch(const uint8_t *src, const int64_t *sel, const int32_t *ys,
                   const int32_t *xs, const int32_t *hs, const int32_t *ws,
                   const uint8_t *flip, uint8_t *out, int64_t b, int64_t sh,
                   int64_t sw, int64_t c, int64_t oh, int64_t ow) {
    const int64_t img_in = sh * sw * c;
    const int64_t row_in = sw * c;
    const int64_t img_out = oh * ow * c;
    const int64_t row_out = ow * c;
#pragma omp parallel
    {
        // Per-thread scratch: 4 column tables + y tables + 2 cached rows.
        std::vector<int32_t> xi0(ow), xi1(ow), wx0(ow), wx1(ow);
        std::vector<int32_t> yi0(oh), yi1(oh), wy0(oh), wy1(oh);
        std::vector<int32_t> rows(2 * row_out);
#pragma omp for schedule(static)
        for (int64_t i = 0; i < b; ++i) {
            const int64_t ch = hs[i], cw = ws[i];
            const uint8_t *crop =
                src + sel[i] * img_in + ys[i] * row_in + (int64_t)xs[i] * c;
            uint8_t *dst = out + i * img_out;
            psl_axis_tables(cw, ow, xi0.data(), xi1.data(), wx0.data(),
                            wx1.data());
            if (flip[i]) {  // mirror the column tables == flip the output
                for (int64_t t = 0; t < ow / 2; ++t) {
                    const int64_t m = ow - 1 - t;
                    std::swap(xi0[t], xi0[m]); std::swap(xi1[t], xi1[m]);
                    std::swap(wx0[t], wx0[m]); std::swap(wx1[t], wx1[m]);
                }
            }
            psl_axis_tables(ch, oh, yi0.data(), yi1.data(), wy0.data(),
                            wy1.data());
            int64_t cached[2] = {-1, -1};   // crop row held in slot [y & 1]
            for (int64_t r = 0; r < oh; ++r) {
                const int64_t y0 = yi0[r], y1 = yi1[r];
                for (int64_t k = 0; k < 2; ++k) {   // ensure rows y0, y1
                    const int64_t y = k ? y1 : y0;
                    int32_t *slot = rows.data() + (y & 1) * row_out;
                    if (cached[y & 1] == y) continue;
                    cached[y & 1] = y;
                    const uint8_t *srow = crop + y * row_in;
                    for (int64_t t = 0; t < ow; ++t) {
                        const uint8_t *p0 = srow + (int64_t)xi0[t] * c;
                        const uint8_t *p1 = srow + (int64_t)xi1[t] * c;
                        const int32_t a = wx0[t], bb = wx1[t];
                        for (int64_t c2 = 0; c2 < c; ++c2)
                            slot[t * c + c2] = a * p0[c2] + bb * p1[c2];
                    }
                }
                const int32_t *r0 = rows.data() + (y0 & 1) * row_out;
                const int32_t *r1 = rows.data() + (y1 & 1) * row_out;
                const int32_t a = wy0[r], bb = wy1[r];
                uint8_t *drow = dst + r * row_out;
                const int32_t half = 1 << (2 * PSL_SHIFT - 1);
                for (int64_t t = 0; t < row_out; ++t)   // contiguous: SIMD
                    drow[t] = (uint8_t)((a * r0[t] + bb * r1[t] + half) >>
                                        (2 * PSL_SHIFT));
            }
        }
    }
}

}  // extern "C"
