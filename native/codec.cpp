// Native codec — the TPU build's equivalent of the reference's c-blosc
// dependency (compression.py drives blosc.pack_array with the snappy codec;
// tools/pre_run.sh installs python-blosc). Same role: fast lossless
// compression of float tensors for network-crossing transfers (here: DCN
// gradient mirrors and checkpoints — ICI allreduce stays uncompressed).
//
// Pipeline: optional byte-shuffle (transpose element bytes so all MSBs are
// contiguous — floats compress far better, same trick blosc uses) + zstd.
// Exposed as a C ABI for ctypes; no pybind11 (not in the image).
//
// Build: make -C native   ->  libpscodec.so

#include <cstdint>
#include <cstring>
#include <zstd.h>

extern "C" {

// Transpose an array of n elements of size `typesize` so that byte k of every
// element is contiguous (blosc-style shuffle).
void psc_shuffle(const uint8_t* src, uint8_t* dst, size_t n, size_t typesize) {
  if (typesize <= 1) { memcpy(dst, src, n); return; }
  const size_t count = n / typesize;
  for (size_t b = 0; b < typesize; ++b) {
    const uint8_t* s = src + b;
    uint8_t* d = dst + b * count;
    for (size_t i = 0; i < count; ++i) d[i] = s[i * typesize];
  }
  // trailing bytes (n not divisible by typesize) copied verbatim
  const size_t tail = n - count * typesize;
  if (tail) memcpy(dst + count * typesize, src + count * typesize, tail);
}

void psc_unshuffle(const uint8_t* src, uint8_t* dst, size_t n, size_t typesize) {
  if (typesize <= 1) { memcpy(dst, src, n); return; }
  const size_t count = n / typesize;
  for (size_t b = 0; b < typesize; ++b) {
    const uint8_t* s = src + b * count;
    uint8_t* d = dst + b;
    for (size_t i = 0; i < count; ++i) d[i * typesize] = s[i];
  }
  const size_t tail = n - count * typesize;
  if (tail) memcpy(dst + count * typesize, src + count * typesize, tail);
}

size_t psc_max_compressed_size(size_t n) { return ZSTD_compressBound(n); }

// Compress n bytes (optionally shuffled with element size `typesize`).
// Returns compressed size, or -1 on error.
long long psc_compress(const uint8_t* src, size_t n, size_t typesize,
                       int level, int do_shuffle, uint8_t* dst,
                       size_t dst_cap, uint8_t* scratch) {
  const uint8_t* payload = src;
  if (do_shuffle && typesize > 1) {
    psc_shuffle(src, scratch, n, typesize);
    payload = scratch;
  }
  const size_t r = ZSTD_compress(dst, dst_cap, payload, n, level);
  if (ZSTD_isError(r)) return -1;
  return (long long)r;
}

// Decompress into dst (n_out = exact original size), then unshuffle.
long long psc_decompress(const uint8_t* src, size_t n_src, size_t typesize,
                         int do_shuffle, uint8_t* dst, size_t n_out,
                         uint8_t* scratch) {
  uint8_t* target = (do_shuffle && typesize > 1) ? scratch : dst;
  const size_t r = ZSTD_decompress(target, n_out, src, n_src);
  if (ZSTD_isError(r) || r != n_out) return -1;
  if (do_shuffle && typesize > 1) psc_unshuffle(scratch, dst, n_out, typesize);
  return (long long)r;
}

}  // extern "C"
