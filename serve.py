#!/usr/bin/env python
"""Serve a ``train_lm.py`` checkpoint over HTTP with continuous batching.

The serving counterpart of ``generate.py``: instead of one ad-hoc decode,
stand up the full request lifecycle — bounded admission queue, slot-based
continuously-batched decode (``ps_pytorch_tpu/serving/``), and hot reload
of newer VALID checkpoints while requests stream (corrupt newest ones are
walked past, same contract as training resume).

    python train_lm.py --lm-corpus-file corpus.txt --train-dir ./lm ...
    python serve.py --train-dir ./lm --serve-port 8300 --serve-slots 8
    curl -s localhost:8300/v1/generate -d '{"prompt": "def train(", "n_new": 64}'

Model geometry comes from the checkpoint's own config; the ``--serve-*``
flags (config.py) size the engine. Byte-level LM: "prompt" is UTF-8 text;
send "tokens" (int list) for non-byte vocabularies.

Fleet mode: pass ``--serve-kv-dir <dir> --serve-replica-id <i>`` and the
replica registers itself in the directory-backed coordination KV
(``serve/<fleet>/replica/<i>``) and beats a liveness lease from the serve
loop; ``tools/router.py``-less fleets just point the Router's FleetView at
the same dir. SIGTERM triggers graceful drain (stop admitting, finish
in-flight, deregister, exit) — the zero-downtime half of a rolling
restart. ``--fault-spec "replica_kill:served=20,r=<i>"`` arms the
drill's SIGKILL. With ``--kv-replicas <spec,...>`` the registry rides the
quorum-replicated coordination plane (``runtime/kvrep.py``) instead of a
single directory, so losing a minority of KV backends never blinds the
router.
"""

import argparse
import dataclasses
import json
import signal
import sys
import time


def main(argv=None) -> int:
    # The --serve-* surface lives in config.py with everything else (one
    # dataclass, config-time validation); serve.py just consumes it.
    from ps_pytorch_tpu.config import TrainConfig, add_train_args

    p = add_train_args(argparse.ArgumentParser(description=__doc__))
    ns = p.parse_args(argv)
    try:
        args = TrainConfig(**{f.name: getattr(ns, f.name)
                              for f in dataclasses.fields(TrainConfig)})
    except ValueError as e:
        p.error(str(e))

    from ps_pytorch_tpu.parallel.dist import _apply_platform_overrides
    _apply_platform_overrides()

    from ps_pytorch_tpu.models.transformer import migrate_packed_qkv
    from ps_pytorch_tpu.runtime import checkpoint as ckpt
    from ps_pytorch_tpu.runtime.lm_eval import (
        build_lm_oracle, build_lm_template, lm_geometry,
    )
    from ps_pytorch_tpu.serving.engine import ServingEngine
    from ps_pytorch_tpu.serving.reload import CheckpointWatcher
    from ps_pytorch_tpu.serving.reqtrace import RequestTraceLog
    from ps_pytorch_tpu.serving.server import ServingFrontend
    from ps_pytorch_tpu.telemetry.health import HealthMonitor
    from ps_pytorch_tpu.telemetry.registry import (
        Registry, declare_serving_metrics,
    )
    from ps_pytorch_tpu.telemetry.slo import SLOTracker

    step = ckpt.latest_valid_step(args.train_dir)
    if step is None:
        p.error(f"no valid model_step_<k> checkpoints in {args.train_dir}")
    with open(f"{ckpt.checkpoint_path(args.train_dir, step)}/config.json") as f:
        cfg = TrainConfig.from_json(f.read())
    if cfg.network != "TransformerLM":
        # The engine's slot decode reuses Block.decode's fixed-length KV
        # cache, which the MoE blocks don't implement.
        p.error(f"serve.py decodes TransformerLM checkpoints; this one is "
                f"{cfg.network} (use generate.py for one-shot MoE decode)")
    template = build_lm_template(cfg)
    _, to_tree = build_lm_oracle(cfg)
    got = ckpt.load_latest_valid(args.train_dir, template,
                                 migrate=migrate_packed_qkv)
    if got is None:
        p.error(f"no restorable checkpoint in {args.train_dir}")
    state, meta, _, step = got

    geo = lm_geometry(cfg)
    registry = Registry()
    declare_serving_metrics(registry)
    # Request-scoped observability plane: lifecycle trace ring
    # (/debug/requests) and SLO burn-rate tracker (/slo), both optional.
    reqtrace = (RequestTraceLog(args.reqtrace_keep,
                                sample=args.reqtrace_sample)
                if args.reqtrace_keep > 0 else None)
    slo = (SLOTracker(args.slo_spec, registry=registry)
           if args.slo_spec else None)
    engine = ServingEngine(
        to_tree(state.params), slots=args.serve_slots,
        vocab=geo["vocab_size"], d_model=geo["d_model"],
        n_layers=geo["n_layers"], n_heads=geo["n_heads"],
        max_seq_len=geo["max_seq_len"], model_step=step, registry=registry,
        reqtrace=reqtrace, slo=slo)
    # Always build the watcher: the periodic poll is gated by
    # --serve-reload-s, but POST /admin/reload (the rolling-reload driver)
    # force-polls regardless.
    watcher = CheckpointWatcher(args.train_dir, template, to_tree=to_tree,
                                migrate=migrate_packed_qkv, start_step=step)
    # Watchdog over the serve loop: the stall detector notices a wedged
    # drive thread (health.beat() runs once per loop iteration) and the
    # state shows up under /healthz's "health" key.
    health = HealthMonitor(args.health_spec or "stall:warn",
                           registry=registry)
    # Identity fields for /healthz: elastic training runs stamp which
    # leadership epoch committed each checkpoint (extra_meta); the serving
    # process itself is a single-process "leader" of its own plane.
    identity = {"leader": True, "role": "serving"}
    for k in ("leader_epoch", "leader_pid"):
        if k in meta:
            identity[k] = meta[k]
    # Fleet plane: registrar (KV record + liveness lease, beaten by the
    # serve loop) and the replica_kill fault injector for the drill.
    registrar = None
    if args.kv_replicas:
        # Quorum-replicated fleet registry: the replica record + liveness
        # lease survive loss of a minority of KV backends, so the router
        # never loses its fleet view to a single dead store.
        from ps_pytorch_tpu.runtime.kvrep import build_replicated_kv
        from ps_pytorch_tpu.serving.router import FleetRegistrar
        fleet_kv = build_replicated_kv(
            args, process_index=args.serve_replica_id)
        registrar = FleetRegistrar(fleet_kv, args.serve_fleet,
                                   args.serve_replica_id)
        identity["replica_id"] = args.serve_replica_id
    elif args.serve_kv_dir:
        from ps_pytorch_tpu.runtime.coordinator import FileKV
        from ps_pytorch_tpu.serving.router import FleetRegistrar
        registrar = FleetRegistrar(FileKV(args.serve_kv_dir),
                                   args.serve_fleet, args.serve_replica_id)
        identity["replica_id"] = args.serve_replica_id
    injector = None
    if args.fault_spec:
        from ps_pytorch_tpu.resilience.faults import FaultInjector
        injector = FaultInjector(args.fault_spec,
                                 process_index=args.serve_replica_id)
    frontend = ServingFrontend(
        engine, watcher=watcher, host=args.serve_host, port=args.serve_port,
        max_queue=args.serve_max_queue, reload_s=args.serve_reload_s,
        default_deadline_s=args.serve_deadline_s,
        default_n_new=args.serve_max_new, health=health, identity=identity,
        max_body_bytes=args.serve_max_body_bytes, registrar=registrar,
        injector=injector, advertise=args.serve_advertise)
    frontend.start()
    print(json.dumps({"serving": f"http://{args.serve_host}:{frontend.port}",
                      "metrics": f"http://{args.serve_host}:{frontend.port}"
                                 "/metrics",
                      "model_step": step, "slots": args.serve_slots,
                      "vocab": geo["vocab_size"],
                      "seq_len": geo["max_seq_len"],
                      "replica_id": (args.serve_replica_id
                                     if registrar else None),
                      "slo_spec": args.slo_spec or None,
                      "reqtrace_keep": args.reqtrace_keep}))
    sys.stdout.flush()

    # SIGTERM = graceful drain: stop admitting, finish in-flight slots,
    # deregister from the fleet, exit 0 — a rolling restart never turns
    # into client-visible errors.
    draining = {"flag": False}

    def _drain(signum, frame):
        draining["flag"] = True

    signal.signal(signal.SIGTERM, _drain)
    try:
        while not draining["flag"]:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        frontend.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
