#!/usr/bin/env python
"""Training entry point — the TPU-native replacement for the reference's
``distributed_nn.py`` + ``run_pytorch.sh`` (and, with a 1-device mesh,
``single_machine.py``: in SPMD the single-machine baseline is just the
degenerate mesh, no separate code path).

No mpirun: on a TPU pod slice, launch this same script on every host
(``python -m ps_pytorch_tpu.tools.launch`` or your pod runner); JAX's
distributed runtime wires the hosts together, the mesh spans all chips, and
each host feeds its own data shard.

Example:
    python train.py --network LeNet --dataset MNIST --batch-size 512 \
        --lr 0.01 --momentum 0.9 --max-steps 1000 --eval-freq 100
"""

import sys


def main(argv=None) -> int:
    from ps_pytorch_tpu.config import config_from_args
    from ps_pytorch_tpu.parallel import dist
    from ps_pytorch_tpu.runtime import Trainer

    # Multi-host bootstrap (no mpirun): tools/launch.py exports the env
    # contract; single-process runs skip this.
    if dist.initialize_from_env():
        import jax
        print(f"DIST process {jax.process_index()}/{jax.process_count()} "
              f"local_devices={jax.local_device_count()}")
    cfg = config_from_args(argv)
    print(f"CONFIG {cfg.to_json()}")
    if cfg.mode == "async":
        import jax
        if jax.process_count() > 1:
            # One slice per process: gradients cross the process/DCN
            # boundary codec-compressed over the coordination-service KV
            # (runtime/async_trainer.py) — the reference's cross-machine
            # async path (resnet_split.py:25-42 staleness tags).
            from ps_pytorch_tpu.runtime.async_trainer import AsyncTrainer
            trainer = AsyncTrainer(cfg)
            print(f"ASYNC process-slices {trainer.n} x "
                  f"{len(trainer.mesh.devices.flat)} devices")
        else:
            # Single process: device groups act as independent slices
            # feeding the aggregator in-process (runtime/multislice.py).
            from ps_pytorch_tpu.runtime.multislice import MultiSliceTrainer
            trainer = MultiSliceTrainer(cfg, n_slices=cfg.async_slices,
                                        fetch_every=cfg.fetch_every)
            print(f"SLICES {cfg.async_slices} x "
                  f"{len(trainer.meshes[0].devices.flat)} devices")
    elif cfg.auto_resume > 0:
        # Crash containment: a failed step loop restarts the trainer, which
        # restores from the latest VALID checkpoint (runtime/checkpoint.py
        # manifest verification) and fast-forwards the data stream. One
        # FaultInjector is threaded across restarts so injected once-only
        # faults (chaos drills) do not re-fire after resume.
        from ps_pytorch_tpu import resilience
        import jax
        injector = None
        if cfg.fault_spec:
            injector = resilience.FaultInjector(
                cfg.fault_spec, process_index=jax.process_index())
        resume_cfg = cfg if cfg.resume else cfg.replace(resume=1)
        built = []

        def make_trainer():
            # First build honours the user's --resume; rebuilds always
            # resume (that is the whole point of the restart).
            t = Trainer(resume_cfg if built else cfg, injector=injector)
            if not built:
                print(f"MESH data={t.mesh.shape['data']} "
                      f"model={t.mesh.shape['model']} "
                      f"devices={len(t.mesh.devices.flat)}")
            built.append(t)
            return t

        resilience.run_with_auto_resume(
            make_trainer, max_restarts=cfg.auto_resume,
            exceptions=(Exception,))
        trainer = built[-1]
        result = trainer.evaluate()
        print(f"FINAL loss {result['loss']:.6f} prec1 {result['prec1']:.4f} "
              f"prec5 {result['prec5']:.4f}")
        return 0
    else:
        trainer = Trainer(cfg)
        print(f"MESH data={trainer.mesh.shape['data']} model={trainer.mesh.shape['model']} "
              f"devices={len(trainer.mesh.devices.flat)}")
    trainer.train()
    result = trainer.evaluate()
    print(f"FINAL loss {result['loss']:.6f} prec1 {result['prec1']:.4f} "
          f"prec5 {result['prec5']:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
